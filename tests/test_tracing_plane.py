"""Self-observability plane: traceparent propagation, span error
handling, abandoned-trace sweep, the dogfood (`_self_` tenant) export
loop, per-query stage waterfalls, and device dispatch timing.

The propagation satellite's core assertion lives in
TestEndToEndSelfTrace: ONE search through the single-binary app yields
ONE trace whose spans cross the frontend→worker→querier boundary with
correct parent/child links, queryable back out of the engine itself.
"""

import re
import threading
import time

import pytest

from tempo_tpu.app import App, AppConfig
from tempo_tpu.db import DBConfig
from tempo_tpu.encoding.common import SearchRequest
from tempo_tpu.model import synth
from tempo_tpu.model.trace import STATUS_ERROR
from tempo_tpu.util import stagetimings, tracing


def make_app(tmp_path, **kw):
    defaults = dict(
        db=DBConfig(backend="local", backend_path=str(tmp_path / "blocks"),
                    wal_path=str(tmp_path / "wal")),
        generator_enabled=False,
    )
    defaults.update(kw)
    return App(AppConfig(**defaults))


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Never leak an exporter into other tests."""
    yield
    tracing.TRACER.exporter = None


# ---------------------------------------------------------------------------
# tracer core: error handling + abandoned-trace sweep (satellite 1)
# ---------------------------------------------------------------------------


class TestTracerErrorHandling:
    def test_exception_sets_status_and_error_attr(self):
        exported = []
        t = tracing.Tracer(exporter=exported.append)
        with pytest.raises(ValueError):
            with t.span("op"):
                raise ValueError("boom")
        span = list(exported[0][0].all_spans())[0]
        assert span.status_code == STATUS_ERROR
        assert span.attributes["error"] == "ValueError: boom"

    def test_nested_exception_marks_every_enclosing_span(self):
        exported = []
        t = tracing.Tracer(exporter=exported.append)
        with pytest.raises(RuntimeError):
            with t.span("root"):
                with t.span("child"):
                    raise RuntimeError("inner")
        spans = {s.name: s for s in exported[0][0].all_spans()}
        assert spans["child"].status_code == STATUS_ERROR
        assert spans["root"].status_code == STATUS_ERROR
        assert "inner" in spans["child"].attributes["error"]

    def test_abandoned_root_swept_and_flushed(self):
        """A child span whose root never finishes (crashed thread) must
        not pin its _open_traces entry forever: the bounded-age sweep
        flushes the partial trace and releases the entry."""
        exported = []
        t = tracing.Tracer(exporter=exported.append, max_open_age_s=5.0)

        # simulate the crash: open root + child on a thread that dies
        # between the child's finish and the root's. The root's context
        # manager is pinned (holds) so GC can't sneak its finally in.
        holds = []

        def crashed():
            root_cm = t.span("root")
            holds.append(root_cm)
            root_cm.__enter__()
            with t.span("child"):
                pass
            # thread "dies" here: root_cm.__exit__ never called

        th = threading.Thread(target=crashed)
        th.start()
        th.join()
        assert t.open_trace_count() == 1
        assert exported == []  # nothing flushed yet

        # too young: sweep keeps it
        assert t.sweep_open(now=time.monotonic() + 1.0) == 0
        assert t.open_trace_count() == 1

        # past max age: flushed as a partial trace, entry released
        assert t.sweep_open(now=time.monotonic() + 10.0) == 1
        assert t.open_trace_count() == 0
        spans = list(exported[0][0].all_spans())
        assert [s.name for s in spans] == ["child"]
        assert spans[0].attributes.get("abandoned") is True

    def test_finish_triggers_opportunistic_sweep(self):
        exported = []
        t = tracing.Tracer(exporter=exported.append, max_open_age_s=0.0)
        t._open_traces[b"x" * 16] = []
        t._open_last[b"x" * 16] = time.monotonic() - 1.0
        t._last_sweep = time.monotonic() - 1.0
        with t.span("normal"):
            pass
        assert t.open_trace_count() == 0  # stale entry swept by _finish


# ---------------------------------------------------------------------------
# W3C traceparent propagation
# ---------------------------------------------------------------------------


class TestTraceparent:
    def test_format_parse_roundtrip(self):
        tid, sid = b"\x01" * 16, b"\x02" * 8
        hdr = tracing.format_traceparent(tid, sid)
        assert re.fullmatch(r"00-[0-9a-f]{32}-[0-9a-f]{16}-01", hdr)
        rp = tracing.parse_traceparent(hdr)
        assert rp.trace_id == tid and rp.span_id == sid

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-abcd-01",
        "00-" + "0" * 32 + "-" + "12" * 8 + "-01",  # zero trace id
        "00-" + "12" * 16 + "-" + "0" * 16 + "-01",  # zero span id
        "00-" + "zz" * 16 + "-" + "12" * 8 + "-01",  # non-hex
    ])
    def test_malformed_headers_ignored(self, bad):
        assert tracing.parse_traceparent(bad) is None

    def test_remote_context_parents_local_spans(self):
        exported = []
        tracing.install_exporter(exported.append)
        tid, sid = b"\xaa" * 16, b"\xbb" * 8
        hdr = tracing.format_traceparent(tid, sid)
        with tracing.remote_context(hdr):
            assert tracing.current_traceparent() == hdr
            with tracing.span("local-root"):
                with tracing.span("local-child"):
                    pass
        # the LOCAL root flushes its fragment under the REMOTE trace id
        spans = {s.name: s for s in exported[0][0].all_spans()}
        assert spans["local-root"].trace_id == tid
        assert spans["local-root"].parent_span_id == sid
        assert spans["local-child"].parent_span_id == spans["local-root"].span_id

    def test_remote_context_does_not_override_active_span(self):
        exported = []
        tracing.install_exporter(exported.append)
        foreign = tracing.format_traceparent(b"\xcc" * 16, b"\xdd" * 8)
        with tracing.span("outer") as outer:
            with tracing.remote_context(foreign):
                with tracing.span("inner"):
                    pass
        spans = {s.name: s for s in exported[0][0].all_spans()}
        assert spans["inner"].trace_id == outer.trace_id
        assert spans["inner"].parent_span_id == outer.span_id

    def test_current_traceparent_none_without_span(self):
        assert tracing.current_traceparent() is None


# ---------------------------------------------------------------------------
# dogfood exporter dampers (rate bound, sampling, governor)
# ---------------------------------------------------------------------------


class _Gov:
    def __init__(self, level):
        self._level = level

    def level(self):
        return self._level


class TestSelfTraceExporter:
    def _traces(self, n=1):
        return synth.make_traces(n, seed=77)

    def test_exports_through_push(self):
        got = []
        exp = tracing.SelfTraceExporter(lambda tenant, traces: got.append((tenant, traces)))
        exp(self._traces(2))
        assert got and got[0][0] == tracing.SELF_TENANT
        assert len(got[0][1]) == 2

    def test_rate_bound_drops_not_blocks(self):
        got = []
        cfg = tracing.SelfTracingConfig(max_spans_per_s=0.0, burst_spans=0.0)
        exp = tracing.SelfTraceExporter(
            lambda tenant, traces: got.append(traces), cfg)
        before = exp.dropped_total.value(reason="rate_limited")
        exp(self._traces(3))
        assert got == []
        assert exp.dropped_total.value(reason="rate_limited") == before + 3

    def test_pressure_drops(self):
        got = []
        exp = tracing.SelfTraceExporter(
            lambda tenant, traces: got.append(traces), governor=_Gov(1))
        exp(self._traces(1))
        assert got == []
        exp.governor = _Gov(0)
        exp(self._traces(1))
        assert got

    def test_push_failure_never_raises(self):
        """Non-amplification: a shed/failed self-push is DROPPED —
        retrying self-traffic during an overload is how observation
        becomes load."""
        from tempo_tpu.util.resource import ResourceExhausted

        def push(tenant, traces):
            raise ResourceExhausted("shed", retry_after_s=5)

        exp = tracing.SelfTraceExporter(push)
        before = exp.dropped_total.value(reason="push_failed")
        exp(self._traces(1))  # must not raise
        assert exp.dropped_total.value(reason="push_failed") == before + 1

    def test_sampling_deterministic(self):
        cfg = tracing.SelfTracingConfig(sample_ratio=0.5)
        exp = tracing.SelfTraceExporter(lambda t, tr: None, cfg)
        traces = synth.make_traces(40, seed=9)
        kept = {t.trace_id for t in traces if exp._sampled(t.trace_id)}
        kept2 = {t.trace_id for t in traces if exp._sampled(t.trace_id)}
        assert kept == kept2  # head sampling is by id, not by dice
        assert 0 < len(kept) < 40


# ---------------------------------------------------------------------------
# end-to-end: one search = one trace across frontend→worker→querier,
# stored in and queryable from the engine itself (`_self_`)
# ---------------------------------------------------------------------------


class TestEndToEndSelfTrace:
    def test_search_yields_one_linked_trace(self, tmp_path):
        app = make_app(
            tmp_path,
            self_tracing=tracing.SelfTracingConfig(enabled=True),
        )
        try:
            app.push_traces(synth.make_traces(8, seed=41))
            app.sweep_all(immediate=True)  # flush so block jobs exist

            hits = app.search(SearchRequest(limit=0))
            assert hits.traces  # the user query itself works

            # the dogfood loop ran synchronously: the frontend span
            # flushed into the `_self_` tenant's live traces. Find it.
            self_hits = app.search(
                SearchRequest(tags={"name": "frontend/search"}, limit=0),
                org_id=tracing.SELF_TENANT,
            )
            assert self_hits.traces, "no self-trace stored under _self_"
            tid = bytes.fromhex(self_hits.traces[0].trace_id_hex)
            trace = app.find_trace(tid, org_id=tracing.SELF_TENANT)
            assert trace is not None
            spans = list(trace.all_spans())
            by_name: dict = {}
            for s in spans:
                by_name.setdefault(s.name, []).append(s)

            # ONE coherent trace: every span carries the same trace id
            assert {s.trace_id for s in spans} == {tid}

            frontend = by_name["frontend/search"][0]
            workers = [s for n, ss in by_name.items() if n.startswith("worker/")
                       for s in ss]
            assert workers, f"no worker spans in {sorted(by_name)}"
            # frontend→worker: the desc-stamped traceparent parents the
            # worker span across the broker boundary
            for w in workers:
                assert w.parent_span_id == frontend.span_id
            # worker→querier: block scans are children of their worker
            block_spans = by_name.get("tempodb/search_block", [])
            assert block_spans, f"no block-scan spans in {sorted(by_name)}"
            worker_ids = {w.span_id for w in workers}
            for b in block_spans:
                assert b.parent_span_id in worker_ids
        finally:
            app.shutdown()

    def test_self_tenant_addressable_without_multitenancy(self, tmp_path):
        app = make_app(tmp_path)
        try:
            assert app.resolve_tenant(tracing.SELF_TENANT) == tracing.SELF_TENANT
            assert app.resolve_tenant(None) == "single-tenant"
        finally:
            app.shutdown()

    def test_shutdown_uninstalls_only_own_exporter(self, tmp_path):
        app = make_app(
            tmp_path, self_tracing=tracing.SelfTracingConfig(enabled=True))
        assert tracing.TRACER.exporter is app._self_exporter
        other = lambda traces: None  # noqa: E731
        tracing.install_exporter(other)
        app.shutdown()
        assert tracing.TRACER.exporter is other  # newer install survives
        tracing.TRACER.exporter = None

    def test_nondistributor_role_exports_via_endpoint(self, tmp_path):
        """Microservices dogfood: a role WITHOUT a distributor ships its
        spans as OTLP/HTTP to self_tracing.endpoint, so query-path spans
        exist in `_self_` even when the frontend/querier/compactor run
        in their own processes."""
        from tempo_tpu.api.server import TempoServer

        sink = make_app(
            tmp_path, self_tracing=tracing.SelfTracingConfig(enabled=False))
        srv = TempoServer(sink).start()
        role = App(AppConfig(
            target="query-frontend",
            db=DBConfig(backend="local",
                        backend_path=str(tmp_path / "blocks"),  # shared store
                        wal_path=str(tmp_path / "wal-fe")),
            generator_enabled=False,
            self_tracing=tracing.SelfTracingConfig(
                enabled=True, endpoint=srv.url),
        ))
        try:
            assert tracing.TRACER.enabled  # the role process records
            with tracing.span("role-span", role="query-frontend"):
                pass
            hits = sink.search(
                SearchRequest(tags={"name": "role-span"}, limit=0),
                org_id=tracing.SELF_TENANT,
            )
            assert hits.traces, "role span never reached the sink's _self_"
        finally:
            role.shutdown()
            srv.stop()
            sink.shutdown()

    def test_role_without_endpoint_records_nothing(self, tmp_path):
        role = App(AppConfig(
            target="query-frontend",
            db=DBConfig(backend="local", backend_path=str(tmp_path / "b"),
                        wal_path=str(tmp_path / "w")),
            generator_enabled=False,
            self_tracing=tracing.SelfTracingConfig(enabled=True),
        ))
        try:
            assert not tracing.TRACER.enabled
        finally:
            role.shutdown()

    def test_push_failure_records_error_span(self, tmp_path, monkeypatch):
        """A push failing under injected faults records STATUS_ERROR
        spans (the flush path here: TEMPO_TPU_FAULTS write errors make
        complete_block fail) WITHOUT amplifying load — the dogfood
        export of those error traces is itself fault-tolerant."""
        monkeypatch.setenv("TEMPO_TPU_FAULTS", "write=1.0,seed=5")
        exported = []
        app = make_app(tmp_path)
        try:
            tracing.install_exporter(exported.append)
            app.push_traces(synth.make_traces(2, seed=42))
            app.sweep_all(immediate=True)  # flush fails on every write
            err_spans = [
                s for tr_list in exported for s in tr_list[0].all_spans()
                if s.status_code == STATUS_ERROR
            ]
            assert err_spans, "injected write faults produced no error spans"
            assert any("ingester/complete_block" == s.name for s in err_spans)
            assert all("error" in s.attributes for s in err_spans)
        finally:
            app.shutdown()


# ---------------------------------------------------------------------------
# stage waterfall
# ---------------------------------------------------------------------------


class TestStageTimings:
    def test_exclusive_nesting(self):
        with stagetimings.request() as st:
            with stagetimings.stage("decode"):
                with stagetimings.stage("fetch"):
                    time.sleep(0.05)
                time.sleep(0.02)
        assert st.seconds["fetch"] >= 0.045
        assert st.seconds["decode"] >= 0.015
        # exclusive: decode does NOT include fetch's 50ms
        assert st.seconds["decode"] < 0.045

    def test_add_counts_once_inside_stage(self):
        with stagetimings.request() as st:
            with stagetimings.stage("decode"):
                stagetimings.add("kernel", 0.5)
        assert st.seconds["kernel"] == 0.5
        assert st.seconds.get("decode", 0.0) < 0.4  # kernel time excluded

    def test_noop_without_active_request(self):
        with stagetimings.stage("fetch"):
            pass
        stagetimings.add("kernel", 1.0)
        stagetimings.count_dispatch()
        assert stagetimings.active() is None

    def test_wire_roundtrip_merge(self):
        a = stagetimings.StageTimings()
        a.add("fetch", 0.25)
        a.count_dispatch(3)
        b = stagetimings.StageTimings()
        b.merge_wire(a.to_wire())
        b.merge_wire(a.to_wire())
        assert b.seconds["fetch"] == pytest.approx(0.5)
        assert b.dispatches == 6

    def test_pool_threads_share_request_accumulator(self):
        from tempo_tpu.db.pool import JobPool

        pool = JobPool(4)
        with stagetimings.request() as st:
            def job():
                with stagetimings.stage("fetch"):
                    time.sleep(0.01)
                return 1

            results, errors = pool.run_jobs([job] * 4)
        assert not errors and len(results) == 4
        assert st.seconds["fetch"] >= 0.035  # all four jobs recorded


class TestSearchWaterfall:
    def test_response_carries_waterfall_summing_to_wall(self, tmp_path):
        # ONE worker so job times serialize: the stage sum is then
        # comparable to wall clock (parallel workers would legitimately
        # sum past it)
        app = make_app(tmp_path, query_workers=1)
        try:
            app.push_traces(synth.make_traces(16, seed=43))
            app.sweep_all(immediate=True)
            t0 = time.perf_counter()
            resp = app.search(SearchRequest(limit=0))
            wall = time.perf_counter() - t0
            assert resp.traces
            assert resp.stage_seconds, "search response carries no waterfall"
            # the worker-side stages travelled back over the job wire
            assert "other" in resp.stage_seconds
            assert "queue_wait" in resp.stage_seconds
            assert "admission" in resp.stage_seconds
            assert "fetch" in resp.stage_seconds  # block IO attributed
            total = sum(resp.stage_seconds.values())
            # stage times account for wall clock without double counting
            # (exclusive nesting). On an idle host the sum lands within
            # ~10% of wall (verified by the e2e drive); here the lower
            # bound is loose because a saturated CI host deschedules
            # threads in gaps no stage owns, and a flaking timing bound
            # teaches people to ignore the gate
            assert total <= wall * 1.25
            assert total >= wall * 0.25
        finally:
            app.shutdown()

    def test_query_range_stats_carry_waterfall(self, tmp_path):
        app = make_app(tmp_path, query_workers=1)
        try:
            app.push_traces(synth.make_traces(8, seed=44))
            app.sweep_all(immediate=True)
            now = int(time.time())
            doc = app.query_range("{} | rate()", now - 120, now + 60, 30)
            stats = doc.get("stats", {})
            assert "stageSeconds" in stats
            assert isinstance(stats["stageSeconds"], dict)
            assert "deviceDispatches" in stats
        finally:
            app.shutdown()

    def test_traceql_stats_carry_waterfall(self, tmp_path):
        app = make_app(tmp_path, query_workers=1)
        try:
            app.push_traces(synth.make_traces(8, seed=45))
            app.sweep_all(immediate=True)
            stats: dict = {}
            hits = app.traceql("{}", stats=stats, limit=0)
            assert hits
            assert isinstance(stats.get("stageSeconds"), dict)
            assert stats["stageSeconds"]  # at least one stage recorded
        finally:
            app.shutdown()


class TestDeviceTiming:
    def test_timed_dispatch_records_histogram_and_stage(self):
        from tempo_tpu.util.devicetiming import dispatch_hist, dispatch_total, timed_dispatch

        before_n = dispatch_hist.count(kernel="unit-test")
        before_c = dispatch_total.value(kernel="unit-test")
        with stagetimings.request() as st:
            out = timed_dispatch("unit-test", lambda x: x + 1, 41)
        assert out == 42
        assert dispatch_hist.count(kernel="unit-test") == before_n + 1
        assert dispatch_total.value(kernel="unit-test") == before_c + 1
        assert st.dispatches == 1
        assert "kernel" in st.seconds

    def test_timed_dispatch_propagates_errors(self):
        from tempo_tpu.util.devicetiming import dispatch_hist, timed_dispatch

        before = dispatch_hist.count(kernel="unit-err")
        with pytest.raises(ValueError):
            timed_dispatch("unit-err", lambda: (_ for _ in ()).throw(ValueError("x")).__next__())
        assert dispatch_hist.count(kernel="unit-err") == before + 1


# ---------------------------------------------------------------------------
# /status/profile formats + device profile
# ---------------------------------------------------------------------------


class TestProfiling:
    def test_collapsed_format_pipes_to_flamegraph(self):
        from tempo_tpu.util.profiling import sample_profile

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(range(2000))

        th = threading.Thread(target=busy, daemon=True)
        th.start()
        try:
            out = sample_profile(0.3, hz=200, fmt="collapsed")
        finally:
            stop.set()
            th.join()
        lines = [ln for ln in out.splitlines() if ln]
        assert lines, "collapsed profile captured nothing"
        for ln in lines:
            # "<root>;...;<leaf> <count>" — flamegraph.pl's input contract
            assert re.fullmatch(r"\S+ \d+", ln), ln
        assert any(";" in ln for ln in lines)

    def test_text_format_unchanged_default(self):
        from tempo_tpu.util.profiling import sample_profile

        out = sample_profile(0.15, hz=100)
        assert out.startswith("# sampling profile:")
        assert "## hottest frames" in out

    def test_profile_endpoints(self, tmp_path):
        import json
        import urllib.request

        from tempo_tpu.api.server import TempoServer

        app = make_app(tmp_path)
        srv = TempoServer(app).start()
        try:
            with urllib.request.urlopen(
                    srv.url + "/status/profile?seconds=0.2&fmt=collapsed") as r:
                assert r.status == 200
            with urllib.request.urlopen(
                    srv.url + "/status/profile/device?seconds=0.2") as r:
                doc = json.loads(r.read())
            assert "supported" in doc
            if doc["supported"]:
                assert doc["dir"]
            # bad fmt is a client error
            try:
                urllib.request.urlopen(srv.url + "/status/profile?fmt=nope")
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()
            app.shutdown()


# ---------------------------------------------------------------------------
# HTTP propagation: client header -> server span
# ---------------------------------------------------------------------------


class TestGrpcPropagation:
    def test_metadata_traceparent_parents_ingest_span(self, tmp_path):
        grpc = pytest.importorskip("grpc")
        from tempo_tpu.receivers import otlp
        from tempo_tpu.receivers.grpc_server import (
            OTLP_EXPORT_METHOD,
            TraceGrpcServer,
        )

        exported = []
        app = make_app(tmp_path)
        srv = TraceGrpcServer(app.push_traces, host="127.0.0.1", port=0).start()
        chan = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        try:
            tracing.install_exporter(exported.append)
            tid, sid = b"\x42" * 16, b"\x24" * 8
            hdr = tracing.format_traceparent(tid, sid)
            payload = otlp.encode_traces_request(synth.make_traces(1, seed=46))
            chan.unary_unary(OTLP_EXPORT_METHOD)(
                payload, metadata=(("traceparent", hdr),))
            grpc_spans = [
                s for tl in exported for s in tl[0].all_spans()
                if s.name == "grpc/export"
            ]
            assert grpc_spans
            assert grpc_spans[0].trace_id == tid
            assert grpc_spans[0].parent_span_id == sid
        finally:
            chan.close()
            srv.stop()
            app.shutdown()


class TestHTTPPropagation:
    def test_client_injects_server_extracts(self, tmp_path):
        from tempo_tpu.api.server import TempoServer
        from tempo_tpu.backend.httpclient import PooledHTTPClient

        exported = []
        app = make_app(tmp_path)
        srv = TempoServer(app).start()
        client = PooledHTTPClient(srv.url)
        try:
            tracing.install_exporter(exported.append)
            with tracing.span("client-root") as root:
                status, _, _ = client.request("GET", "/api/search?limit=5")
            assert status == 200

            # the server's http span landed in the CLIENT's trace. The
            # server span closes AFTER it writes the response, so under
            # host load the client can get here first — poll boundedly
            # rather than flake on the export race.
            def http_spans_now():
                return [
                    s for tl in exported for s in tl[0].all_spans()
                    if s.name.startswith("http/GET /api/search")
                ]

            deadline = time.monotonic() + 5.0
            http_spans = http_spans_now()
            while not http_spans and time.monotonic() < deadline:
                time.sleep(0.02)
                http_spans = http_spans_now()
            assert http_spans, [
                s.name for tl in exported for s in tl[0].all_spans()]
            assert http_spans[0].trace_id == root.trace_id
            assert http_spans[0].parent_span_id == root.span_id
        finally:
            client.close()
            srv.stop()
            app.shutdown()
