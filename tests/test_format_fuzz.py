"""Seeded fuzz for the page-encoding tier (lightweight + entropy).

Tier-1 contract for every codec that can appear in PageMeta:
- round trips are exact for random dtypes/shapes/run structures,
  including empty and single-element pages;
- the stored crc (of the DECODED payload) verifies, and a flipped crc
  is detected;
- truncating the page at any boundary raises CorruptPage — never a
  silently wrong array (PR 6: corruption is never served);
- run-/dict-/gather-space reads agree with the full decode.

Seeds are fixed so failures replay bit-identically.
"""

from __future__ import annotations

import numpy as np
import pytest

from tempo_tpu.encoding.vtpu import codec, format as fmt, lightweight as lw

SEEDS = (0, 1, 2)


def _random_array(rng, kind: str):
    """Arrays shaped like real column pages, per target codec."""
    n = int(rng.choice([0, 1, 2, 7, 127, 128, 129, 1000, 4096]))
    if kind == "rle":
        # run-heavy, sometimes 2-D (trace-ID limb rows)
        if rng.random() < 0.5:
            vals = rng.integers(0, 50, max(n // max(int(rng.integers(1, 9)), 1), 1))
            arr = np.repeat(vals, rng.integers(1, 9, len(vals)))[:n].astype(np.uint32)
            if len(arr) < n:
                arr = np.concatenate([arr, np.zeros(n - len(arr), np.uint32)])
        else:
            base = rng.integers(0, 2**32, (max(n // 4, 1), 4)).astype(np.uint32)
            arr = np.repeat(base, 4, axis=0)[:n]
        return arr
    if kind == "dbp":
        dt = rng.choice([np.uint32, np.uint64])
        if rng.random() < 0.3:
            return np.sort(rng.integers(0, 2**30, (n, 4)).astype(np.uint32), axis=0)
        deltas = rng.integers(-(2**20), 2**20, n)
        return (np.int64(2**40) + np.cumsum(deltas)).astype(dt)
    if kind == "dct":
        d = int(rng.choice([1, 2, 17, 200]))
        if rng.random() < 0.5:
            return rng.integers(0, max(d, 1), n).astype(np.uint32)
        pool = rng.integers(0, 2**32, (max(d, 1), 2)).astype(np.uint32)
        return pool[rng.integers(0, len(pool), n)]
    # entropy tier: anything integral
    dt = rng.choice([np.uint8, np.uint32, np.uint64])
    return rng.integers(0, 2**31, n).astype(dt)


def _codecs_under_test():
    out = ["none", "zlib", "rle", "dbp", "dct"]
    from tempo_tpu import native

    if native.lib() is not None:
        out += ["zstd", "zstd_shuffle"]
    return out


class TestRoundTripFuzz:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_every_codec_round_trips(self, seed):
        rng = np.random.default_rng(seed)
        for c in _codecs_under_test():
            kind = c if c in ("rle", "dbp", "dct") else "entropy"
            for _ in range(12):
                arr = _random_array(rng, kind)
                page, crc = codec.encode(arr, c)
                out = codec.decode(page, arr.dtype.str, arr.shape, c, crc)
                assert out.dtype == arr.dtype and out.shape == arr.shape
                assert (out == arr).all(), (c, arr.shape, arr.dtype)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_crc_flip_detected(self, seed):
        rng = np.random.default_rng(100 + seed)
        for c in _codecs_under_test():
            kind = c if c in ("rle", "dbp", "dct") else "entropy"
            arr = _random_array(rng, kind)
            while arr.size == 0:
                arr = _random_array(rng, kind)
            page, crc = codec.encode(arr, c)
            with pytest.raises(codec.CorruptPage):
                codec.decode(page, arr.dtype.str, arr.shape, c, crc ^ 0xDEAD)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_truncation_raises_not_garbage(self, seed):
        rng = np.random.default_rng(200 + seed)
        for c in _codecs_under_test():
            kind = c if c in ("rle", "dbp", "dct") else "entropy"
            arr = _random_array(rng, kind)
            while arr.size < 16:
                arr = _random_array(rng, kind)
            page, crc = codec.encode(arr, c)
            cuts = sorted({0, 1, 3, len(page) // 4, len(page) // 2, len(page) - 1})
            for cut in cuts:
                with pytest.raises(codec.CorruptPage):
                    codec.decode(page[:cut], arr.dtype.str, arr.shape, c, crc)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_mangled_body_raises(self, seed):
        """Bit flips inside the page body must be caught by the body or
        payload crc, for the run-space reads too."""
        rng = np.random.default_rng(300 + seed)
        for c in ("rle", "dbp", "dct"):
            arr = _random_array(rng, c)
            while arr.size < 64:
                arr = _random_array(rng, c)
            page, crc = codec.encode(arr, c)
            flip = bytearray(page)
            pos = int(rng.integers(8, len(flip)))
            flip[pos] ^= 0x40
            with pytest.raises(codec.CorruptPage):
                codec.decode(bytes(flip), arr.dtype.str, arr.shape, c, crc)


class TestEncodedSpaceReads:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_rle_runs_and_gather_match_decode(self, seed):
        rng = np.random.default_rng(400 + seed)
        for _ in range(8):
            arr = _random_array(rng, "rle")
            page, crc = codec.encode(arr, "rle")
            full = codec.decode(page, arr.dtype.str, arr.shape, "rle", crc)
            values, lengths = lw.rle_decode_runs(page, arr.dtype.str, arr.shape)
            assert (np.repeat(values, lengths, axis=0) == full).all()
            if arr.shape[0]:
                rows = np.sort(rng.choice(arr.shape[0], min(13, arr.shape[0]), replace=False))
                assert (lw.rle_gather(values, lengths, rows) == full[rows]).all()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_dbp_gather_matches_decode(self, seed):
        rng = np.random.default_rng(500 + seed)
        for _ in range(8):
            arr = _random_array(rng, "dbp")
            page, crc = codec.encode(arr, "dbp")
            full = codec.decode(page, arr.dtype.str, arr.shape, "dbp", crc)
            if arr.shape[0]:
                rows = np.sort(rng.choice(arr.shape[0], min(29, arr.shape[0]), replace=False))
                got, touched = lw.dbp_gather(page, arr.dtype.str, arr.shape, rows)
                assert (got == full[rows]).all()
                assert touched <= arr.shape[0] + lw.DBP_MINIBLOCK

    @pytest.mark.parametrize("seed", SEEDS)
    def test_dct_indices_and_gather_match_decode(self, seed):
        rng = np.random.default_rng(600 + seed)
        for _ in range(8):
            arr = _random_array(rng, "dct")
            page, crc = codec.encode(arr, "dct")
            full = codec.decode(page, arr.dtype.str, arr.shape, "dct", crc)
            values, idx = lw.dct_indices(page, arr.dtype.str, arr.shape)
            if arr.shape[0]:
                assert (values[idx].reshape(arr.shape) == full).all()
                rows = np.sort(rng.choice(arr.shape[0], min(13, arr.shape[0]), replace=False))
                assert (lw.dct_gather(page, arr.dtype.str, arr.shape, rows) == full[rows]).all()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_device_decode_parity(self, seed):
        """The device dbp decode (two-limb prefix scan) and rle expand
        are bit-identical to the host decode."""
        from tempo_tpu.ops import pallas_kernels as pk

        rng = np.random.default_rng(700 + seed)
        for _ in range(4):
            arr = _random_array(rng, "dbp")
            page, crc = codec.encode(arr, "dbp")
            host = codec.decode(page, arr.dtype.str, arr.shape, "dbp", crc)
            dev = pk.dbp_decode_device(page, arr.dtype.str, arr.shape)
            assert (host == dev).all()
        arr = _random_array(rng, "rle")
        while arr.ndim != 1 or arr.size == 0:
            arr = _random_array(rng, "rle")
        page, crc = codec.encode(arr, "rle")
        values, lengths = lw.rle_decode_runs(page, arr.dtype.str, arr.shape)
        dev = np.asarray(pk.rle_expand_device(
            values.astype(np.uint32), lengths.astype(np.int32), arr.shape[0]))
        assert (dev == arr.astype(np.uint32)).all()


class TestFusedKernels:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_fused_rle_in_set_matches_host(self, seed):
        """The batched fused decode+predicate program equals per-row
        np.isin over the expanded columns."""
        from tempo_tpu.ops import pallas_kernels as pk

        rng = np.random.default_rng(800 + seed)
        U, C, K, R, n = 3, 2, 4, 32, 256
        values = rng.integers(0, 10, (U, C, R)).astype(np.uint32)
        lengths = np.zeros((U, C, R), np.int32)
        for u in range(U):
            for c in range(C):
                lengths[u, c] = rng.multinomial(n, np.ones(R) / R)
        codes = np.full((U, C, K), 0xFFFFFFFF, np.uint32)
        codes[:, :, :2] = rng.integers(0, 10, (U, C, 2))
        masks = pk.fused_rle_in_set(values, lengths, codes, n)
        for u in range(U):
            want = np.ones(n, bool)
            for c in range(C):
                col = np.repeat(values[u, c], lengths[u, c])
                want &= np.isin(col, codes[u, c][codes[u, c] != 0xFFFFFFFF])
            assert (masks[u] == want).all()

    def test_unshuffle_device_inverts_byte_shuffle(self):
        from tempo_tpu.ops import pallas_kernels as pk

        rng = np.random.default_rng(5)
        x = rng.integers(0, 2**32, 4096).astype(np.uint32)
        planes = x.view(np.uint8).reshape(-1, 4).T.copy()  # blosc shuffle
        assert (np.asarray(pk.unshuffle_device(planes, 4)) == x).all()


class TestChooser:
    def test_chooser_deterministic_and_bounded(self):
        rng = np.random.default_rng(9)
        svc = np.repeat(rng.integers(0, 5, 512).astype(np.uint32), 8)
        assert lw.choose_codec("service", svc, "zlib") == "rle"
        assert lw.choose_codec("service", svc, "zlib") == "rle"  # stable
        # high-entropy column refuses every lightweight codec
        rnd = rng.integers(0, 2**63, 4096).astype(np.uint64)
        assert lw.choose_codec("duration_nano", rnd, "zlib") == "zlib"
        # kill switch
        import os

        os.environ["TEMPO_TPU_LIGHTWEIGHT"] = "0"
        try:
            assert lw.choose_codec("service", svc, "zlib") == "zlib"
        finally:
            os.environ.pop("TEMPO_TPU_LIGHTWEIGHT")

    def test_tiny_pages_stay_on_default(self):
        arr = np.zeros(8, np.uint32)
        assert lw.choose_codec("service", arr, "zlib") == "zlib"


class TestPlanPageRuns:
    def test_shuffled_pages_dict_plans_by_offset(self):
        """plan_page_runs must sort by OFFSET, not dict order: after
        relocation/reencode mixes the pages dict can interleave
        arbitrarily vs the byte layout (the regression this pins)."""
        import random

        names = [f"c{i}" for i in range(8)]
        pages = {}
        off = 0
        metas = []
        for n in names:
            ln = 100 + 10 * len(metas)
            metas.append((n, off, ln))
            off += ln + 50  # 50-byte gaps, below any sane max_gap
        random.Random(7).shuffle(metas)
        for n, o, ln in metas:
            pages[n] = fmt.PageMeta(offset=o, length=ln, dtype="<u4",
                                    shape=(25,), codec="none", crc=0)
        rg = fmt.RowGroupMeta(n_spans=25, n_attrs=0, min_id="0", max_id="f",
                              start_s=0, end_s=1, pages=pages)
        runs = fmt.plan_page_runs(rg, list(pages), max_gap=64)
        # one run (gaps all 50 <= 64), covering the true byte span
        assert len(runs) == 1
        lo, hi, run_names = runs[0]
        assert lo == min(o for _, o, _ in metas)
        assert hi == max(o + ln for _, o, ln in metas)
        assert sorted(run_names) == sorted(names)
        # and with zero tolerance, one run per page, offset-ordered
        runs = fmt.plan_page_runs(rg, list(pages), max_gap=0)
        offs = [lo for lo, _, _ in runs]
        assert offs == sorted(offs) and len(runs) == len(names)
