"""TraceQL tests: parser corpus (valid/invalid), evaluation semantics,
condition pushdown + storage-layer conformance — mirroring the
reference's table-driven test_examples.yaml + ast_execute_test.go +
block_traceql_test.go strategy."""

import numpy as np
import pytest

from tempo_tpu.backend import MockBackend, TypedBackend
from tempo_tpu.db import DBConfig, TempoDB
from tempo_tpu.encoding import default_encoding
from tempo_tpu.encoding.common import BlockConfig
from tempo_tpu.model import synth
from tempo_tpu.model import trace as tr
from tempo_tpu.traceql import ParseError, execute, parse
from tempo_tpu.traceql import ast_nodes as A
from tempo_tpu.traceql.engine import EvalContext, eval_spanset_expr

VALID = [
    "{}",
    '{ name = "GET /api" }',
    "{ duration > 100ms }",
    "{ duration >= 1.5s && status = error }",
    '{ .region = "eu" || .retry.count > 3 }',
    '{ span.level = 2 }',
    '{ resource.cluster = "test" }',
    '{ resource.service.name = "cart" }',
    "{ kind = server }",
    "{ childCount > 2 }",
    "{ parent = nil }",
    '{ parent.name = "root" }',
    '{ name =~ "GET.*" }',
    '{ !(.level = 5) }',
    "{ duration > 2 * 50ms }",
    "{ .a + 1 > 2 }",
    "{} | count() > 2",
    "{ status = error } | avg(duration) > 100ms",
    "{} | min(.level) < 3",
    "{} | coalesce()",
    '{ name = "a" } && { name = "b" }',
    '{ name = "a" } || { name = "b" }',
    '{ name = "parent" } > { name = "child" }',
    '{ name = "root" } >> { .deep = true }',
    '({ name = "a" } || { name = "b" }) | count() > 1',
    "{ 1 = 1 }",
    "{ true }",
]

INVALID = [
    "",
    "{",
    "{ name = }",
    "{ name =~ 5 }",  # regex needs string
    "{} | count()",  # aggregate needs comparison
    "{} | frobnicate()",
    "{ name && }",
    "nonsense",
    "{ .a = 1 } |",
]


class TestParser:
    @pytest.mark.parametrize("q", VALID)
    def test_valid(self, q):
        parse(q)

    @pytest.mark.parametrize("q", INVALID)
    def test_invalid(self, q):
        with pytest.raises(ParseError):
            parse(q)

    def test_precedence(self):
        p = parse('{ .a = 1 && .b = 2 || .c = 3 }')
        expr = p.stages[0].expr
        assert isinstance(expr, A.Binary) and expr.op == "||"

    def test_duration_literal(self):
        p = parse("{ duration > 1.5s }")
        assert p.stages[0].expr.rhs.value == 1_500_000_000


def trace_fixture():
    """root(server,100ms) -> child1(err,.level=5,200ms) -> grandchild(10ms)
                          -> child2(ok,.level=1,50ms)"""
    tid = b"\x01" * 16
    mk = lambda sid, name, parent, dur, status=0, kind=2, attrs=None: tr.Span(
        trace_id=tid, span_id=sid, name=name, parent_span_id=parent,
        start_unix_nano=10**18, duration_nano=dur, status_code=status,
        kind=kind, attributes=attrs or {},
    )
    root = mk(b"\x0a" * 8, "root", b"\x00" * 8, 100_000_000, kind=2)
    c1 = mk(b"\x0b" * 8, "child1", root.span_id, 200_000_000, status=2, kind=3,
            attrs={"level": 5, "region": "eu"})
    gc = mk(b"\x0c" * 8, "grand", c1.span_id, 10_000_000, attrs={"deep": True})
    c2 = mk(b"\x0d" * 8, "child2", root.span_id, 50_000_000, status=1,
            attrs={"level": 1})
    t = tr.Trace(trace_id=tid, batches=[({"service.name": "svc", "cluster": "c1"},
                                         [root, c1, gc, c2])])
    return t


def run_query(q, traces=None):
    traces = traces if traces is not None else [trace_fixture()]
    return execute(q, lambda spec, s, e: traces, limit=0)


class TestEvaluation:
    def test_name_eq(self):
        r = run_query('{ name = "child1" }')
        assert len(r) == 1 and [s.name for s in r[0].spans] == ["child1"]

    def test_match_all(self):
        r = run_query("{}")
        assert len(r[0].spans) == 4

    def test_duration_cmp(self):
        r = run_query("{ duration > 90ms }")
        assert {s.name for s in r[0].spans} == {"root", "child1"}

    def test_status_keyword(self):
        r = run_query("{ status = error }")
        assert {s.name for s in r[0].spans} == {"child1"}

    def test_kind_keyword(self):
        r = run_query("{ kind = client }")
        assert {s.name for s in r[0].spans} == {"child1"}

    def test_attr_numeric(self):
        r = run_query("{ .level > 2 }")
        assert {s.name for s in r[0].spans} == {"child1"}

    def test_attr_missing_is_false(self):
        r = run_query('{ .nope = "x" }')
        assert r == []

    def test_resource_attr(self):
        r = run_query('{ resource.cluster = "c1" }')
        assert len(r[0].spans) == 4

    def test_parent_nil_root(self):
        r = run_query("{ parent = nil }")
        assert {s.name for s in r[0].spans} == {"root"}

    def test_parent_attr(self):
        r = run_query("{ parent.level = 5 }")
        assert {s.name for s in r[0].spans} == {"grand"}

    def test_child_count(self):
        r = run_query("{ childCount = 2 }")
        assert {s.name for s in r[0].spans} == {"root"}

    def test_regex(self):
        r = run_query('{ name =~ "child." }')
        assert {s.name for s in r[0].spans} == {"child1", "child2"}
        r = run_query('{ name !~ "child." }')
        assert {s.name for s in r[0].spans} == {"root", "grand"}

    def test_not(self):
        r = run_query("{ !(status = error) }")
        assert {s.name for s in r[0].spans} == {"root", "grand", "child2"}

    def test_arithmetic(self):
        r = run_query("{ duration > 2 * 60ms }")
        assert {s.name for s in r[0].spans} == {"child1"}
        r = run_query("{ .level + 1 >= 6 }")
        assert {s.name for s in r[0].spans} == {"child1"}

    def test_bool_attr(self):
        r = run_query("{ .deep = true }")
        assert {s.name for s in r[0].spans} == {"grand"}

    def test_spanset_and(self):
        r = run_query('{ name = "child1" } && { name = "child2" }')
        assert {s.name for s in r[0].spans} == {"child1", "child2"}
        assert run_query('{ name = "child1" } && { name = "zzz" }') == []

    def test_spanset_or(self):
        r = run_query('{ name = "child1" } || { name = "zzz" }')
        assert {s.name for s in r[0].spans} == {"child1"}

    def test_child_op(self):
        r = run_query('{ name = "root" } > { status = error }')
        assert {s.name for s in r[0].spans} == {"child1"}
        assert run_query('{ name = "root" } > { name = "grand" }') == []

    def test_descendant_op(self):
        r = run_query('{ name = "root" } >> { name = "grand" }')
        assert {s.name for s in r[0].spans} == {"grand"}

    def test_count_aggregate(self):
        assert run_query("{} | count() > 3")[0].spans
        assert run_query("{} | count() > 4") == []
        assert run_query("{ status = error } | count() = 1")[0].spans

    def test_avg_aggregate(self):
        # avg(duration) = (100+200+10+50)/4 = 90ms
        assert run_query("{} | avg(duration) = 90000000")
        assert run_query("{} | avg(duration) > 100ms") == []
        assert run_query("{} | max(duration) = 200ms")
        assert run_query("{} | min(.level) = 1")
        assert run_query("{} | sum(.level) = 6")

    def test_result_metadata(self):
        r = run_query('{ name = "grand" }')[0]
        assert r.root_trace_name == "root"
        assert r.root_service_name == "svc"
        assert r.trace_id_hex == ("01" * 16)


class TestConditionExtraction:
    def get_spec(self, q):
        return parse(q).conditions()

    def test_and_extracts_all(self):
        spec = self.get_spec('{ name = "a" && duration > 1s }')
        assert spec.all_conditions and len(spec.conditions) == 2

    def test_or_not_all(self):
        spec = self.get_spec('{ name = "a" || duration > 1s }')
        assert not spec.all_conditions and len(spec.conditions) == 2

    def test_opaque_or_no_pushdown(self):
        spec = self.get_spec('{ name = "a" || .x + 1 > 2 }')
        assert spec.conditions == []

    def test_opaque_and_keeps_supported(self):
        spec = self.get_spec('{ name = "a" && .x + 1 > 2 }')
        assert spec.all_conditions and len(spec.conditions) == 1

    def test_spanset_and_never_all(self):
        spec = self.get_spec('{ name = "a" } && { name = "b" }')
        assert not spec.all_conditions and len(spec.conditions) == 2


class TestStorageConformance:
    """End-to-end through a real block: pushdown + engine must equal
    pure-engine evaluation over all traces (no lost matches)."""

    QUERIES = [
        '{ resource.service.name = "frontend" }',
        '{ name =~ "GET.*" }',
        "{ duration > 500ms }",
        "{ status = error }",
        '{ .region = "42" }',
        "{ .level >= 3 }",
        "{ .http.status_code = 500 }",
        '{ .http.method = "POST" && duration < 1s }',
        "{ status = error } | count() >= 2",
        '{ kind = server } >> { status = error }',
        "{ parent = nil && duration > 100ms }",
    ]

    @pytest.fixture(scope="class")
    def db(self):
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        traces = synth.make_traces(40, seed=77)
        db.write_batch("t", tr.traces_to_batch(traces).sorted_by_trace())
        db.write_batch("t", tr.traces_to_batch(synth.make_traces(20, seed=78)).sorted_by_trace())
        self_traces = traces + synth.make_traces(20, seed=78)
        db._all_traces = self_traces
        return db

    def test_cross_block_structural_query(self):
        """A trace straddling blocks where one block's spans don't match
        the pushdown must still evaluate structural/aggregate operators
        over the WHOLE trace."""
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        t = trace_fixture()
        resource = t.batches[0][0]
        spans = list(t.all_spans())
        by_name = {s.name: s for s in spans}
        # block 1: only root; block 2: the children/grandchild
        t_a = tr.Trace(trace_id=t.trace_id, batches=[(resource, [by_name["root"]])])
        t_b = tr.Trace(
            trace_id=t.trace_id,
            batches=[(resource, [by_name["child1"], by_name["grand"], by_name["child2"]])],
        )
        db.write_batch("t", tr.traces_to_batch([t_a]).sorted_by_trace())
        db.write_batch("t", tr.traces_to_batch([t_b]).sorted_by_trace())
        # pushdown for name="root" only matches block 1; childCount needs
        # the children that live in block 2
        got = db.traceql_search("t", '{ name = "root" && childCount = 2 }', limit=0)
        assert len(got) == 1 and {s.name for s in got[0].spans} == {"root"}
        got = db.traceql_search("t", '{ name = "root" } >> { name = "grand" }', limit=0)
        assert len(got) == 1 and {s.name for s in got[0].spans} == {"grand"}

    @pytest.mark.parametrize("q", QUERIES)
    def test_pushdown_matches_full_eval(self, db, q):
        got = db.traceql_search("t", q, limit=0)
        want = execute(q, lambda spec, s, e: db._all_traces, limit=0)
        assert {r.trace_id_hex for r in got} == {r.trace_id_hex for r in want}, q
        # matched span sets agree too
        gm = {r.trace_id_hex: {s.span_id for s in r.spans} for r in got}
        wm = {r.trace_id_hex: {s.span_id for s in r.spans} for r in want}
        assert gm == wm, q


class TestTimeWindow:
    def test_engine_filters_out_of_window_traces(self):
        """Fetchers prune only at row-group/block granularity; the engine
        must re-check the window exactly (regression: live-ingester path
        returned everything regardless of start/end)."""
        t_old = synth.make_trace(1, base_time_ns=1_000 * 10**9)
        t_new = synth.make_trace(2, base_time_ns=5_000 * 10**9)
        fetch = lambda spec, s, e: [t_old, t_new]
        got = execute("{ }", fetch, start_s=4_000, end_s=6_000, limit=0)
        assert {r.trace_id_hex for r in got} == {t_new.trace_id.hex()}
        got = execute("{ }", fetch, start_s=500, end_s=6_000, limit=0)
        assert len(got) == 2
        got = execute("{ }", fetch, limit=0)  # no window -> everything
        assert len(got) == 2


class TestNewStages:
    """Sibling op, by(), select(), leading aggregates, wrapped pipelines
    (reference: OpSpansetSibling, groupOperation, expr.y BY/coalesce)."""

    def test_sibling(self):
        # child1 and child2 share parent root; grand has no sibling
        r = run_query('{ name = "child1" } ~ { name = "child2" }')
        assert {s.name for s in r[0].spans} == {"child2"}
        r = run_query('{ name = "grand" } ~ { name = "grand" }')
        assert r == []  # a span is not its own sibling

    def test_sibling_requires_other_span(self):
        r = run_query('{ name = "child2" } ~ { name = "child2" }')
        assert r == []

    def test_by_groups_then_count(self):
        # group spans by status: error group has 1, others 3
        r = run_query('{} | by(status) | count() >= 2')
        # groups: status 0 (root+grand), 1 (child2), 2 (child1) -> only
        # the status-0 group survives
        assert {s.name for s in r[0].spans} == {"root", "grand"}

    def test_by_then_coalesce_restores_all(self):
        r = run_query('{} | by(status) | coalesce()')
        assert len(r[0].spans) == 4

    def test_by_drops_trace_when_no_group_passes(self):
        r = run_query('{} | by(name) | count() > 1')
        assert r == []  # every name group has exactly one span

    def test_leading_count(self):
        assert len(run_query('count() = 4')[0].spans) == 4
        assert run_query('count() = 3') == []

    def test_select_attaches_fields(self):
        r = run_query('{ name = "child1" } | select(.level, duration)')
        (res,) = r
        sid = res.spans[0].span_id
        vals = res.span_attrs[sid]
        assert vals[".level"] == 5
        assert vals["duration"] == 200_000_000
        d = res.to_dict()
        attrs = d["spanSet"]["spans"][0]["attributes"]
        assert {"key": ".level", "value": {"intValue": "5"}} in attrs

    def test_wrapped_pipeline_operand(self):
        # lhs pipeline keeps only traces where the error-count = 1 and
        # yields the error span; rhs children of that span
        r = run_query('({ status = error } | count() = 1) > { duration < 20ms }')
        assert {s.name for s in r[0].spans} == {"grand"}

    def test_second_filter_stage(self):
        r = run_query('{ duration > 40ms } | { status = error }')
        assert {s.name for s in r[0].spans} == {"child1"}


class TestVectorObjectParity:
    """Vector path must agree with the object engine or fall back to it
    (review findings: stage order, dedicated-column scopes, runtime
    data-shape bailouts, wrapped pipeline stages)."""

    def _db_with(self, traces):
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        db.write_batch("t", tr.traces_to_batch(traces).sorted_by_trace())
        return db

    def _check(self, db, traces, q):
        got = db.traceql_search("t", q, limit=0)
        want = execute(q, lambda spec, s, e: traces, limit=0)
        assert {r.trace_id_hex for r in got} == {r.trace_id_hex for r in want}, q
        gm = {r.trace_id_hex: {s.span_id for s in r.spans} for r in got}
        wm = {r.trace_id_hex: {s.span_id for s in r.spans} for r in want}
        assert gm == wm, q

    def test_filter_after_aggregate_matches_object_engine(self):
        t = trace_fixture()
        db = self._db_with([t])
        # count() must observe all 4 spans BEFORE the second filter
        self._check(db, [t], "{} | count() = 4 | { status = error }")
        assert db.traceql_search("t", "{} | count() = 3 | { status = error }", limit=0) == []

    def test_span_scope_does_not_see_resource_service(self):
        t = trace_fixture()
        db = self._db_with([t])
        # service.name lives on the resource: span-scope must not match
        self._check(db, [t], '{ span.service.name = "svc" }')
        self._check(db, [t], '{ resource.service.name = "svc" }')

    def test_resource_scope_http_method_uses_attr_table(self):
        t = trace_fixture()
        # resource attr named http.method (NOT the span dedicated column)
        t.batches[0][0]["http.method"] = "TRACE"
        db = self._db_with([t])
        self._check(db, [t], '{ resource.http.method = "TRACE" }')
        self._check(db, [t], '{ span.http.method = "TRACE" }')

    def test_mixed_type_attr_falls_back(self):
        tid = b"\x07" * 16
        mk = lambda sid, val: tr.Span(
            trace_id=tid, span_id=sid, name="op", parent_span_id=b"\x00" * 8,
            start_unix_nano=10**18, duration_nano=1000,
            attributes={"flaky": val},
        )
        t = tr.Trace(trace_id=tid, batches=[({"service.name": "s"},
                                             [mk(b"\x01" * 8, 1), mk(b"\x02" * 8, "one")])])
        db = self._db_with([t])
        # int on one span, string on the other: vector path raises
        # Unsupported at eval time; db must fall back, not 500
        self._check(db, [t], "{ .flaky = 1 }")
        self._check(db, [t], '{ .flaky = "one" }')

    def test_wrapped_pipeline_as_stage(self):
        t = trace_fixture()
        db = self._db_with([t])
        got = db.traceql_search("t", "{ true } | ({ status = error } | count() = 1)", limit=0)
        assert len(got) == 1 and {s.name for s in got[0].spans} == {"child1"}

    def test_string_ordering_falls_back(self):
        t = trace_fixture()
        db = self._db_with([t])
        # lexicographic name comparison: vector must bail to object path
        self._check(db, [t], '{ name > "childZ" }')
        self._check(db, [t], '{ name <= "child1" }')

    def test_cross_block_root_name(self):
        tid = b"\x09" * 16
        mk = lambda sid, name, parent, svc: tr.Trace(
            trace_id=tid,
            batches=[({"service.name": svc},
                      [tr.Span(trace_id=tid, span_id=sid, name=name,
                               parent_span_id=parent, start_unix_nano=10**18,
                               duration_nano=1000)])],
        )
        root_sid, child_sid = b"\x01" * 8, b"\x02" * 8
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        # block 1 holds only the CHILD; block 2 holds the true root
        db.write_batch("t", tr.traces_to_batch([mk(child_sid, "child", root_sid, "svc-child")]).sorted_by_trace())
        db.write_batch("t", tr.traces_to_batch([mk(root_sid, "THEROOT", b"\x00" * 8, "svc-root")]).sorted_by_trace())
        (got,) = db.traceql_search("t", "{}", limit=0)
        assert got.root_trace_name == "THEROOT"
        assert got.root_service_name == "svc-root"

    def test_by_groups_match_object_engine(self):
        t = trace_fixture()
        db = self._db_with([t])
        # group by name: every group has count 1 -> count() > 1 drops all
        assert db.traceql_search("t", "{} | by(name) | count() > 1", limit=0) == []
        # group by status: two kind-2 spans (root+grand share status 0)
        self._check(db, [t], "{} | by(status) | count() > 1")
        # group by attr; spans without .level form their own (None) group
        self._check(db, [t], "{} | by(.level) | count() > 1")
        self._check(db, [t], "{} | by(.region) | count() = 1")
        # grouped non-count aggregates
        self._check(db, [t], "{} | by(status) | avg(duration) > 50ms")
        self._check(db, [t], "{ status != error } | by(name) | max(duration) >= 100ms")
        # by + arithmetic key
        self._check(db, [t], "{} | by(1 + .level) | count() = 1")

    def test_by_groups_merge_across_blocks(self):
        tid = b"\x31" * 16
        mk = lambda sid, name, dur: tr.Trace(
            trace_id=tid,
            batches=[({"service.name": "s"},
                      [tr.Span(trace_id=tid, span_id=sid, name=name,
                               parent_span_id=b"\x00" * 8, start_unix_nano=10**18,
                               duration_nano=dur)])],
        )
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        # same group value ("op") split across two blocks with different
        # dictionaries: counts must merge before the aggregate resolves
        db.write_batch("t", tr.traces_to_batch([mk(b"\x01" * 8, "op", 1000)]).sorted_by_trace())
        db.write_batch("t", tr.traces_to_batch([mk(b"\x02" * 8, "op", 3000)]).sorted_by_trace())
        (got,) = db.traceql_search("t", "{} | by(name) | count() = 2", limit=0)
        assert got.trace_id_hex == tid.hex()
        assert db.traceql_search("t", "{} | by(name) | count() = 1", limit=0) == []

    def test_select_attaches_fields(self):
        t = trace_fixture()
        db = self._db_with([t])
        self._check(db, [t], '{ name = "child1" } | select(.level, .region)')
        (got,) = db.traceql_search("t", '{ name = "child1" } | select(.level, .region)', limit=0)
        (want,) = execute('{ name = "child1" } | select(.level, .region)',
                          lambda spec, s, e: [t], limit=0)
        g = {k.hex(): v for k, v in got.span_attrs.items()}
        w = {k.hex(): v for k, v in want.span_attrs.items()}
        assert g == w and g  # {'level': 5, 'region': 'eu'} on child1
        # to_dict carries the attributes through
        d = got.to_dict()
        attrs = d["spanSet"]["spans"][0]["attributes"]
        assert {a["key"] for a in attrs} == {".level", ".region"}

    def test_select_preserves_stored_value_type(self):
        """A float attr with an integral value must stay doubleValue on
        both engines; int attrs stay intValue (review finding)."""
        tid = b"\x41" * 16
        sp = tr.Span(trace_id=tid, span_id=b"\x01" * 8, name="op",
                     parent_span_id=b"\x00" * 8, start_unix_nano=10**18,
                     duration_nano=1000,
                     attributes={"ratio": 2.0, "retries": 2})
        t = tr.Trace(trace_id=tid, batches=[({"service.name": "s"}, [sp])])
        db = self._db_with([t])
        q = "{} | select(.ratio, .retries)"
        (got,) = db.traceql_search("t", q, limit=0)
        (want,) = execute(q, lambda spec, s, e: [t], limit=0)
        gv = got.span_attrs[sp.span_id]
        wv = want.span_attrs[sp.span_id]
        assert gv == wv
        assert isinstance(gv[".ratio"], float) and isinstance(gv[".retries"], int)
        d = got.to_dict()["spanSet"]["spans"][0]["attributes"]
        byk = {a["key"]: a["value"] for a in d}
        assert "doubleValue" in byk[".ratio"] and "intValue" in byk[".retries"]

    def test_select_mixed_scope_int_float(self):
        """An any-scope attr stored VT_FLOAT on one span (span scope) and
        VT_INT on another (resource scope) must render each span's
        STORED type on both engines (review finding)."""
        tid = b"\x42" * 16
        a = tr.Span(trace_id=tid, span_id=b"\x01" * 8, name="a",
                    parent_span_id=b"\x00" * 8, start_unix_nano=10**18,
                    duration_nano=1000, attributes={"x": 1.5})
        b = tr.Span(trace_id=tid, span_id=b"\x02" * 8, name="b",
                    parent_span_id=b"\x00" * 8, start_unix_nano=10**18,
                    duration_nano=1000)
        t = tr.Trace(trace_id=tid, batches=[({"service.name": "s", "x": 2}, [a, b])])
        db = self._db_with([t])
        q = "{} | select(.x)"
        (got,) = db.traceql_search("t", q, limit=0)
        (want,) = execute(q, lambda spec, s, e: [t], limit=0)
        assert got.span_attrs == want.span_attrs
        assert isinstance(got.span_attrs[a.span_id][".x"], float)
        assert isinstance(got.span_attrs[b.span_id][".x"], int)

    def test_select_truncation_attrs_match(self):
        """span_attrs must cover exactly the kept (capped) spans on both
        engines when matched spans exceed the cap (review finding)."""
        tid = b"\x43" * 16
        spans = [tr.Span(trace_id=tid, span_id=i.to_bytes(8, "big"), name="op",
                         parent_span_id=b"\x00" * 8,
                         start_unix_nano=10**18 + i, duration_nano=1000,
                         attributes={"level": i})
                 for i in range(1, 31)]
        t = tr.Trace(trace_id=tid, batches=[({"service.name": "s"}, spans)])
        db = self._db_with([t])
        q = "{} | select(.level)"
        (got,) = db.traceql_search("t", q, limit=0)
        (want,) = execute(q, lambda spec, s, e: [t], limit=0)
        assert got.span_attrs == want.span_attrs
        assert len(got.span_attrs) == 20  # the kept spans only
        assert got.matched_override == want.matched_override == 30

    def test_select_intrinsics_and_missing(self):
        t = trace_fixture()
        db = self._db_with([t])
        self._check(db, [t], "{} | select(duration, name)")
        self._check(db, [t], "{} | select(.does_not_exist)")

    def test_object_fallback_reports_bytes(self):
        t = trace_fixture()
        db = self._db_with([t])
        stats = {}
        db.traceql_search("t", "{} | by(status) | coalesce()", limit=0, stats=stats)  # -> object path
        assert stats.get("inspectedBytes", 0) > 0
        assert stats.get("inspectedBlocks", 0) >= 1


class TestVectorObjectFuzz:
    """Seeded differential fuzz: random supported queries over random
    traces (split across two blocks) must produce identical results on
    the vector path and the object engine (reference analog: the
    table-driven fetch conformance of vparquet/block_traceql_test.go)."""

    _FILTERS = [
        "{}",
        '{ name = "op3" }',
        '{ name =~ "op[12]" }',
        "{ duration > 40ms }",
        "{ status = error }",
        "{ kind = server }",
        "{ .level > 2 }",
        '{ .region = "eu" }',
        "{ .flag = true }",
        "{ .ratio >= 1.5 }",
        '{ status != error && .level <= 4 }',
        '{ name = "op1" || .region = "ap" }',
        "{ parent = nil }",
        "{ !(.level = 3) }",
    ]
    _BYS = [None, "by(name)", "by(status)", "by(.region)", "by(.level)", "by(1 + .level)"]
    _AGGS = [None, "count() > 1", "count() = 2", "avg(duration) > 50ms",
             "max(.level) >= 3", "sum(.ratio) < 4", "min(duration) <= 80ms"]
    _SELECTS = [None, "select(name, duration)", "select(.level, .region, .ratio)"]
    # structural spanset expressions: always the object engine, so this
    # arm fuzzes pushdown + cross-block trace reassembly rather than
    # engine parity
    _STRUCTURAL = [
        '{ name = "op1" } && { name = "op2" }',
        '{ .level > 2 } || { .region = "eu" }',
        '{ parent = nil } > { duration > 20ms }',
        '{ name =~ "op." } >> { status = error }',
        '{ kind = server } ~ { kind = client }',
    ]

    def _random_traces(self, rng, n_traces=12):
        regions = ["eu", "us", "ap"]
        traces = []
        for i in range(n_traces):
            tid = rng.getrandbits(128).to_bytes(16, "big")
            spans = []
            n_spans = rng.randint(1, 6)
            for j in range(n_spans):
                attrs = {}
                if rng.random() < 0.7:
                    attrs["level"] = rng.randint(0, 5)
                if rng.random() < 0.5:
                    attrs["region"] = rng.choice(regions)
                if rng.random() < 0.3:
                    attrs["flag"] = rng.random() < 0.5
                if rng.random() < 0.4:
                    attrs["ratio"] = rng.choice([0.5, 1.5, 2.0, 3.25])
                spans.append(tr.Span(
                    trace_id=tid,
                    span_id=rng.getrandbits(64).to_bytes(8, "big"),
                    name=f"op{rng.randint(1, 4)}",
                    parent_span_id=(b"\x00" * 8 if j == 0 else spans[0].span_id),
                    start_unix_nano=10**18 + rng.randint(0, 10**9),
                    duration_nano=rng.choice([10, 30, 50, 80, 120]) * 10**6,
                    status_code=rng.choice([0, 0, 1, 2]),
                    kind=rng.choice([1, 2, 3]),
                    attributes=attrs,
                ))
            traces.append(tr.Trace(
                trace_id=tid,
                batches=[({"service.name": f"svc{i % 3}"}, spans)],
            ))
        return traces

    def test_fuzz_parity(self):
        import random

        from tempo_tpu.traceql import vector

        rng = random.Random(1234)
        checked = vectorized = 0
        for round_i in range(40):
            traces = self._random_traces(rng)
            db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
            # split each trace's spans across two blocks (merge coverage)
            half_a, half_b = [], []
            for t in traces:
                res, spans = t.batches[0]
                k = len(spans) // 2
                if k:
                    half_a.append(tr.Trace(trace_id=t.trace_id, batches=[(res, spans[:k])]))
                half_b.append(tr.Trace(trace_id=t.trace_id, batches=[(res, spans[k:])]))
            db.write_batch("t", tr.traces_to_batch(half_a).sorted_by_trace())
            db.write_batch("t", tr.traces_to_batch(half_b).sorted_by_trace())

            for _ in range(8):
                if rng.random() < 0.25:
                    parts = [rng.choice(self._STRUCTURAL)]
                else:
                    parts = [rng.choice(self._FILTERS)]
                by = rng.choice(self._BYS)
                if by:
                    parts.append(by)
                agg = rng.choice(self._AGGS)
                if agg:
                    parts.append(agg)
                sel = rng.choice(self._SELECTS)
                if sel:
                    parts.append(sel)
                q = " | ".join(parts)
                pipeline = parse(q)
                if vector.supports(pipeline):
                    vectorized += 1
                # occasionally constrain the time window; traces start in
                # [10**9, 10**9+1] s, so the second window DROPS almost
                # every trace (sub-second start offsets) while the first
                # keeps all — both sides of the prune get exercised
                kw = {}
                r = rng.random()
                if r < 0.15:
                    kw = {"start_s": 10**9 - 10, "end_s": 10**9 + 10}
                elif r < 0.3:
                    kw = {"start_s": 1, "end_s": 10**9}
                got = db.traceql_search("t", q, limit=0, **kw)
                want = execute(q, lambda spec, s, e, _t=traces: _t, limit=0, **kw)
                gm = {r.trace_id_hex: (set(s.span_id for s in r.spans),
                                       r.matched_override if r.matched_override >= 0 else len(r.spans),
                                       {k.hex(): v for k, v in r.span_attrs.items()})
                      for r in got}
                wm = {r.trace_id_hex: (set(s.span_id for s in r.spans),
                                      r.matched_override if r.matched_override >= 0 else len(r.spans),
                                      {k.hex(): v for k, v in r.span_attrs.items()})
                      for r in want}
                assert gm == wm, f"query {q!r} diverged (round {round_i})"
                checked += 1
        assert checked == 320 and vectorized > 150, (checked, vectorized)

    def test_fuzz_parity_whole_traces(self):
        """Same differential fuzz with traces NOT split across blocks:
        structural queries (spanset ops, parent.*, childCount) stay on
        the vectorized path (no straddling -> no object fallback) and
        must match the object engine span-for-span."""
        import random

        from tempo_tpu.traceql import vector

        structural_qs = self._STRUCTURAL + [
            "{ childCount > 1 }",
            "{ childCount = 0 }",
            '{ parent.level > 2 }',
            '{ name = "op2" } | { parent = nil } > { duration > 10ms }',
            '{ .level > 1 } >> { .region = "eu" }',
            '({ name = "op1" } || { name = "op2" }) ~ { status = error }',
        ]
        rng = random.Random(4321)
        checked = 0
        for round_i in range(12):
            traces = self._random_traces(rng)
            db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
            half = len(traces) // 2
            db.write_batch("t", tr.traces_to_batch(traces[:half]).sorted_by_trace())
            db.write_batch("t", tr.traces_to_batch(traces[half:]).sorted_by_trace())
            for q in structural_qs:
                pipeline = parse(q)
                assert vector.supports(pipeline), q
                got = db.traceql_search("t", q, limit=0)
                want = execute(q, lambda spec, s, e, _t=traces: _t, limit=0)
                gm = {r.trace_id_hex: (set(s.span_id for s in r.spans),
                                       r.matched_override if r.matched_override >= 0 else len(r.spans))
                      for r in got}
                wm = {r.trace_id_hex: (set(s.span_id for s in r.spans),
                                       r.matched_override if r.matched_override >= 0 else len(r.spans))
                      for r in want}
                assert gm == wm, f"query {q!r} diverged (round {round_i})"
                checked += 1
        assert checked == 12 * len(structural_qs)

    def test_structural_deep_tree_parity(self):
        """Multi-level trees (not just root fan-out): >> must close over
        grandparent chains and ~ must group by parent-id value."""
        import random

        from tempo_tpu.traceql import vector

        rng = random.Random(99)
        traces = []
        for i in range(10):
            tid = rng.getrandbits(128).to_bytes(16, "big")
            spans = []
            for j in range(rng.randint(2, 10)):
                parent = (b"\x00" * 8 if j == 0
                          else spans[rng.randrange(len(spans))].span_id)
                spans.append(tr.Span(
                    trace_id=tid,
                    span_id=rng.getrandbits(64).to_bytes(8, "big"),
                    name=f"op{rng.randint(1, 3)}",
                    parent_span_id=parent,
                    start_unix_nano=10**18 + j,
                    duration_nano=rng.choice([10, 50, 120]) * 10**6,
                    status_code=rng.choice([0, 2]),
                    kind=2,
                    attributes={"level": rng.randint(0, 4)},
                ))
            traces.append(tr.Trace(trace_id=tid, batches=[({"service.name": "s"}, spans)]))
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        db.write_batch("t", tr.traces_to_batch(traces).sorted_by_trace())
        for q in [
            '{ name = "op1" } >> { name = "op2" }',
            '{ parent = nil } >> { status = error }',
            '{ .level > 0 } > { .level > 0 }',
            '{ name = "op1" } ~ { name = "op1" }',
            "{ childCount > 0 } > { childCount = 0 }",
            '{ parent.name = "op1" }',
            "{ parent.level >= 2 }",
        ]:
            assert vector.supports(parse(q)), q
            got = db.traceql_search("t", q, limit=0)
            want = execute(q, lambda spec, s, e: traces, limit=0)
            gm = {r.trace_id_hex: set(s.span_id for s in r.spans) for r in got}
            wm = {r.trace_id_hex: set(s.span_id for s in r.spans) for r in want}
            assert gm == wm, f"query {q!r} diverged"

    def test_straddle_guard_falls_back_exactly(self):
        """A structural query over a tenant where ONE trace straddles two
        blocks must produce object-engine answers (combined traces), not
        per-block structural joins."""
        import random

        rng = random.Random(7)
        traces = self._random_traces(rng, n_traces=6)
        db = TempoDB(DBConfig(backend="mock"), raw_backend=MockBackend())
        # trace 0 split across blocks; rest whole in block A
        t0 = traces[0]
        res, spans = t0.batches[0]
        assert len(spans) >= 2 or True
        k = max(1, len(spans) // 2)
        frag_a = tr.Trace(trace_id=t0.trace_id, batches=[(res, spans[:k])])
        frag_b = tr.Trace(trace_id=t0.trace_id, batches=[(res, spans[k:])])
        db.write_batch("t", tr.traces_to_batch([frag_a] + traces[1:]).sorted_by_trace())
        db.write_batch("t", tr.traces_to_batch([frag_b]).sorted_by_trace())
        for q in ['{ parent = nil } > {}', "{ childCount >= 0 }"]:
            got = db.traceql_search("t", q, limit=0)
            want = execute(q, lambda spec, s, e: traces, limit=0)
            gm = {r.trace_id_hex: set(s.span_id for s in r.spans) for r in got}
            wm = {r.trace_id_hex: set(s.span_id for s in r.spans) for r in want}
            assert gm == wm, f"query {q!r} diverged"
